"""Extracted transition model of the RPC request lifecycle.

The front-door request path of :mod:`.frontdoor` + :mod:`.replica_main`
reduced to an explicit-state machine for `analysis/protocol_check.py`:
one rid's life across retries, a hedge, drain re-routing, replica
crashes and the replica idempotency store, enumerated exhaustively so
the exactly-once claims the RPC chaos matrix spot-checks
(RPC_CHAOS.json) hold in EVERY interleaving of the small world, not
just the sampled ones.

Pinned to the implementation:

- terminal classification uses the production error taxonomy — a failed
  rid carries :class:`~.rpc.RpcTimeout`'s / :class:`~.rpc.RpcShed`'s /
  :class:`~.rpc.RpcConnRefused`'s pinned ``.code`` strings (imported,
  not restated), and ``tests/test_control_plane_analysis.py`` pins the
  model's code set against the classes;
- the replica intake mirrors ``ReplicaServer._handle``'s order: drain
  refusal → idempotency-store replay (``engine.completed``) → execute;
  the ``"replay_miss"`` mutation skips exactly the store check, which
  is what makes re-execution of a completed rid reachable;
- delivery is the front door's first-writer-wins ``_deliver``: the
  first usable result resolves the rid, a hedge loser is a wasted RPC,
  never a second delivery.

Honest limits: one rid and two replicas (the lifecycle invariants are
per-rid; hedging needs exactly two parties), bounded attempts, the
wire abstracted to {result, refusal, loss} (framing/CRC tears are the
ctrlfile trailer's proven layer — a torn frame surfaces here as the
``lost`` outcome), and deadlines as a nondeterministically-enabled
expiry transition.

Mutation: ``"replay_miss"`` (the idempotency store misses on replay —
a retried rid re-executes on the same replica).

:class:`MigrationModel` is the disaggregation twin: one rid's KV
migration handshake (export → ship → admit-or-refuse → release) between
a prefill replica and a decode replica, with the decode crash injectable
at every phase.  Its invariants are the handoff's two safety claims — a
crash mid-migration never LOSES the request (every quiescent state is a
loud terminal) and never LEAKS the prefill-side export (the exported
blocks are released on exactly the ack/abort edges
``replica_main._handle_migrate`` releases them on).  Mutation:
``"skip_release"`` (the abort paths — decode refusal, ship failure —
skip ``engine.release_exported``, which is what makes the block leak
reachable).
"""

from __future__ import annotations

from .migration import MigrationError
from .rpc import RpcConnRefused, RpcShed, RpcTimeout

__all__ = [
    "RpcModel",
    "RPC_MUTATIONS",
    "TERMINAL_STATUSES",
    "FAIL_CODES",
    "MigrationModel",
    "MIGRATION_MUTATIONS",
]

RPC_MUTATIONS = ("replay_miss",)
MIGRATION_MUTATIONS = ("skip_release",)

# the exactly-one-of terminal set ("every rid lands in exactly one of
# completed-once / shed / failed")
TERMINAL_STATUSES = ("completed", "shed", "failed")
INFLIGHT = "inflight"
# a failed rid's classification comes from the production taxonomy
FAIL_CODES = (RpcTimeout.code, RpcConnRefused.code, RpcShed.code)

_N_REPLICAS = 2


class RpcModel:
    """State = (fd, replicas, attempts, budgets).

    ``fd``: ``(status, delivered)`` — the front door's terminal record
    for the rid and how many results were delivered to the caller.
    ``replicas``: per replica ``(alive, draining, in_store, execs)`` —
    ``in_store`` is ``engine.completed``'s verdict for the rid,
    ``execs`` counts actual engine executions (the quantity the
    no-re-execution invariant bounds).  ``attempts``: in-flight
    ``(replica, outcome)`` pairs, outcome in {sent, result, drain,
    shed, error}.  ``budgets``: ``(dispatches, crashes, drains)``.
    """

    name_prefix = "rpc"

    def __init__(self, *, dispatches: int = 3, crashes: int = 1,
                 drains: int = 1, mutation: str | None = None):
        if mutation is not None and mutation not in RPC_MUTATIONS:
            raise ValueError(f"unknown rpc mutation: {mutation}")
        self.mutation = mutation
        self.budget0 = (dispatches, crashes, drains)
        self.name = f"{self.name_prefix}@{_N_REPLICAS}replicas"
        if mutation:
            self.name += f"+{mutation}"

    def initial(self):
        replicas = tuple((True, False, False, 0) for _ in range(_N_REPLICAS))
        return ((INFLIGHT, 0), replicas, (), self.budget0)

    def is_fault_label(self, label: str) -> bool:
        return label.startswith(("crash", "drain"))

    # ---- transitions -------------------------------------------------------

    def transitions(self, state):
        fd, replicas, attempts, budgets = state
        status, delivered = fd
        dispatches, crashes, drains = budgets
        out = []

        # -- intake shed: the front door refuses at the door (only
        #    before any attempt exists — shed_outstanding at submit)
        if status == INFLIGHT and not attempts and dispatches == \
                self.budget0[0]:
            out.append((f"shed_intake({RpcShed.code})",
                        (("shed", delivered), replicas, attempts, budgets),
                        []))

        # -- dispatch an attempt (retry after a failed one, or a hedge
        #    beside an outstanding one — at most 2 concurrent, distinct
        #    replicas, mirroring max_hedges=1)
        if status == INFLIGHT and dispatches > 0 and len(attempts) < 2:
            used = {r for r, _ in attempts}
            for r in range(_N_REPLICAS):
                if r in used:
                    continue  # a hedge goes to a DIFFERENT replica
                alive = replicas[r][0]
                na = attempts + ((r, "sent" if alive else "error"),)
                out.append((f"dispatch(rep{r})",
                            (fd, replicas, na,
                             (dispatches - 1, crashes, drains)), []))

        # -- replica processes a sent attempt: ReplicaServer._handle's
        #    order — drain refusal, then the idempotency store, then
        #    execute
        for i, (r, phase) in enumerate(attempts):
            if phase != "sent":
                continue
            alive, draining, in_store, execs = replicas[r]
            if not alive:
                continue  # crash transition already failed its attempts
            if draining:
                out.append((f"refuse_drain(rep{r})",
                            (fd, replicas,
                             _set(attempts, i, (r, "drain")), budgets), []))
                continue
            # backlog shed: max_pending reached at intake (the backlog
            # itself is other rids' traffic, abstracted to the refusal)
            out.append((f"refuse_shed(rep{r})",
                        (fd, replicas, _set(attempts, i, (r, "shed")),
                         budgets), []))
            viol = []
            if in_store and self.mutation != "replay_miss":
                # dedup replay: answered from the store, no execution
                nr = replicas
            else:
                if in_store:
                    viol.append((
                        "completed-rid-reexecuted",
                        f"rid re-executed on replica {r} with its result "
                        "already in the idempotency store (store check "
                        "skipped) — exactly-once is now at-least-twice",
                    ))
                nr = _set(replicas, r, (alive, draining, True, execs + 1))
            out.append((f"execute(rep{r})",
                        (fd, nr, _set(attempts, i, (r, "result")), budgets),
                        viol))
            # the response can also be lost in flight (torn frame, reset
            # mid-reply): the replica DID execute, the caller sees error
            out.append((f"respond_lost(rep{r})",
                        (fd, nr, _set(attempts, i, (r, "error")), budgets),
                        viol))

        # -- front door harvests a finished attempt
        for i, (r, phase) in enumerate(attempts):
            if phase == "sent":
                continue
            na = attempts[:i] + attempts[i + 1:]
            if phase == "result":
                if status == INFLIGHT:
                    nfd = ("completed", delivered + 1)
                else:
                    nfd = fd  # late/hedge-loser result: dropped, never a
                    # second delivery (first-writer-wins _deliver)
                out.append((f"deliver(rep{r})", (nfd, replicas, na, budgets),
                            []))
            else:  # drain / shed / error → retry elsewhere or give up
                out.append((f"drop_attempt(rep{r},{phase})",
                            (fd, replicas, na, budgets), []))
                if status == INFLIGHT and not na and dispatches == 0:
                    code = (RpcShed.code if phase == "shed"
                            else RpcConnRefused.code)
                    out.append((f"fail({code})",
                                (("failed", delivered), replicas, na,
                                 budgets), []))

        # -- deadline expiry: always possible while unresolved (the
        #    budget the caller stops waiting at) — outstanding attempts
        #    keep running as waste, their results are dropped above
        if status == INFLIGHT:
            out.append((f"deadline({RpcTimeout.code})",
                        (("failed", delivered), replicas, attempts, budgets),
                        []))

        # -- fault injection at every transition: replica crash (its
        #    in-flight attempts all error at once — _fail_all) and
        #    SIGTERM drain
        if crashes > 0:
            for r in range(_N_REPLICAS):
                alive, draining, in_store, execs = replicas[r]
                if not alive:
                    continue
                nr = _set(replicas, r, (False, draining, in_store, execs))
                na = tuple((ar, "error" if (ar == r and ph == "sent") else ph)
                           for ar, ph in attempts)
                out.append((f"crash(rep{r})",
                            (fd, nr, na, (dispatches, crashes - 1, drains)),
                            []))
        if drains > 0:
            for r in range(_N_REPLICAS):
                alive, draining, in_store, execs = replicas[r]
                if not alive or draining:
                    continue
                nr = _set(replicas, r, (alive, True, in_store, execs))
                out.append((f"drain(rep{r})",
                            (fd, nr, attempts,
                             (dispatches, crashes, drains - 1)), []))
        return out

    # ---- invariants --------------------------------------------------------

    def state_violations(self, state):
        """Every reachable state: delivery and execution bounds."""
        (status, delivered), replicas, attempts, budgets = state
        viols = []
        if delivered > 1:
            viols.append((
                "double-delivery",
                f"rid delivered {delivered} times — completed-once means "
                "exactly once",
            ))
        if delivered and status != "completed":
            viols.append((
                "terminal-mismatch",
                f"rid delivered a result yet terminal status is {status}",
            ))
        for r, (alive, draining, in_store, execs) in enumerate(replicas):
            if execs > 1:
                viols.append((
                    "completed-rid-reexecuted",
                    f"rid executed {execs} times on replica {r} — the "
                    "idempotency store must answer replays",
                ))
        return viols

    def quiescent_violations(self, state):
        (status, delivered), replicas, attempts, budgets = state
        viols, truncated = [], False
        if status not in TERMINAL_STATUSES:
            viols.append((
                "unresolved-rid",
                f"quiescent with rid status {status} — every rid must land "
                f"in exactly one of {TERMINAL_STATUSES}",
            ))
        if status == "completed" and delivered != 1:
            viols.append((
                "terminal-mismatch",
                f"completed rid delivered {delivered} results",
            ))
        return viols, truncated


class MigrationModel:
    """State = ``(status, exported, decode_alive, decode_has_seq,
    attempts, crashes)``.

    ``status`` is the front door's view of the rid: ``inflight`` (no
    handoff running — the colocated fallback and the deadline live
    here), ``exported`` (prefill done, blocks parked in
    ``engine._exported``, ship unresolved), ``admitted`` (decode
    verified + admitted, ack delivered to the prefill side),
    ``handed_off`` (export released on ack; the sequence lives on the
    decode replica), and the terminals ``completed`` / ``failed``.
    ``exported`` tracks the prefill-side blocks the release handshake
    must free exactly once; ``decode_has_seq`` tracks whether the decode
    replica holds the migrated sequence (dies with the process — paged
    blocks are process memory, so a crash frees them and is never a
    leak).  Budgets: ``attempts`` bounds front-door launches (export,
    local fallback, collect re-route), ``crashes`` bounds decode-replica
    deaths.

    Honest limits: one rid, one prefill and one decode replica, the wire
    abstracted to {ack, refuse, lost} (CRC/shape refusals of a poisoned
    payload surface as ``refuse`` — the byte-level checks are
    ``unpack_kv``'s tested layer), and the deadline only fires between
    handoff rounds (the front door abandons between rounds; the replica
    halves of a mid-flight handshake still run to their release edges,
    which is exactly what the implementation's synchronous
    ``_handle_migrate`` does)."""

    name_prefix = "migration"

    def __init__(self, *, attempts: int = 3, crashes: int = 2,
                 mutation: str | None = None):
        if mutation is not None and mutation not in MIGRATION_MUTATIONS:
            raise ValueError(f"unknown migration mutation: {mutation}")
        self.mutation = mutation
        self.budget0 = (attempts, crashes)
        self.name = f"{self.name_prefix}@1hop"
        if mutation:
            self.name += f"+{mutation}"

    def initial(self):
        return ("inflight", False, True, False) + self.budget0

    def is_fault_label(self, label: str) -> bool:
        return label.startswith(("crash", "drain"))

    # ---- transitions -------------------------------------------------------

    def transitions(self, state):
        status, exported, alive, has_seq, attempts, crashes = state
        out = []

        def _abort(label, *, seq=has_seq):
            # release_exported(acked=False) — the edge the
            # ``skip_release`` mutation deletes
            freed = exported if self.mutation == "skip_release" else False
            out.append((label,
                        ("inflight", freed, alive, seq, attempts, crashes),
                        []))

        if status == "inflight":
            if attempts > 0 and not exported:
                # prefill_for_migration: prefill + first token + export
                out.append(("export",
                            ("exported", True, alive, has_seq,
                             attempts - 1, crashes), []))
            if attempts > 0:
                # the migrate-vs-local fallback: a colocated (or other
                # decode-tier) replica serves the rid without the hop
                out.append(("complete_local",
                            ("completed", exported, alive, has_seq,
                             attempts - 1, crashes), []))
            # deadline expiry: the caller stops waiting
            out.append((f"deadline({RpcTimeout.code})",
                        ("failed", exported, alive, has_seq, attempts,
                         crashes), []))

        elif status == "exported":
            if alive:
                # admit-or-refuse, plus the ack lost in flight AFTER the
                # decode side already admitted (reply torn mid-stream):
                # the prefill side aborts either way, the decode side
                # keeps the sequence it admitted
                out.append(("admit_ack",
                            ("admitted", exported, alive, True, attempts,
                             crashes), []))
                _abort(f"refuse({MigrationError.code})")
                _abort("ship_lost_after_admit", seq=True)
            else:
                # receiver unreachable / died mid-stream
                _abort(f"ship_fail({RpcConnRefused.code})")

        elif status == "admitted":
            # the ack already landed: release_exported(acked=True) is
            # unconditional, crash or no crash on the decode side
            out.append(("release_ack",
                        ("handed_off", False, alive, has_seq, attempts,
                         crashes), []))

        elif status == "handed_off":
            if alive:
                out.append(("complete_remote",
                            ("completed", exported, alive, has_seq,
                             attempts, crashes), []))
            elif attempts > 0:
                # decode died holding the sequence: the collect attempt
                # errors and the front door re-routes (the sequence died
                # with the process — greedy decode recomputes bitwise)
                out.append(("collect_retry",
                            ("inflight", exported, alive, False,
                             attempts - 1, crashes), []))
            else:
                out.append((f"deadline({RpcTimeout.code})",
                            ("failed", exported, alive, has_seq, attempts,
                             crashes), []))

        # -- fault injection: the decode replica can crash at any phase;
        #    its admitted sequence (and blocks) die with the process
        if crashes > 0 and alive and status not in ("completed", "failed"):
            out.append(("crash(decode)",
                        (status, exported, False, False, attempts,
                         crashes - 1), []))
        return out

    # ---- invariants --------------------------------------------------------

    def state_violations(self, state):
        return []

    def quiescent_violations(self, state):
        status, exported, alive, has_seq, attempts, crashes = state
        viols = []
        if status not in ("completed", "failed"):
            viols.append((
                "unresolved-rid",
                f"quiescent with rid status {status} — a crash mid-"
                "migration must resolve to a loud terminal, never lose "
                "the request",
            ))
        if exported:
            viols.append((
                "migration-block-leak",
                "quiescent with the prefill-side export still held — "
                "release_exported must run on every ack AND abort edge, "
                "or each failed handoff leaks blocks_for(prompt) blocks "
                "until the pool starves",
            ))
        return viols, False


def _set(tup, i, row):
    return tup[:i] + (row,) + tup[i + 1:]
