"""The serving engine: paged cache + continuous batcher + the model.

One :class:`ServingEngine` is one replica: it owns a paged K/V pool, a
:class:`~flextree_tpu.serving.batcher.ContinuousBatcher`, and two jitted
programs — prefill (one compile per distinct prompt length) and the paged
decode step (ONE compile for the server lifetime; slot count, table
width, and pool shape are all static).  The decode step runs **fused**
paged attention by default (``fused=True`` → ``ops.paged_attention``
streams K/V blocks through an online softmax, never materializing the
gathered row; within a pinned tolerance of the gather oracle);
``fused=False`` keeps the gather path, which is the one proven bitwise
against ``generate``.  ``step()`` is one scheduling round:

1. **resume** — preempted sequences re-enter free slots with strict
   priority (swap-in scatter of their saved K/V, or prefill-replay
   recompute), continuing bit-identically where they stopped;
2. **admit** — pop queued requests into free slots under the block
   (reservation or on-demand, per ``BatcherConfig.admission``) and
   prefill-token budgets; each admitted request runs prefill, scatters
   its K/V into its blocks, and emits its first token (that's the TTFT
   moment — continuous batching's whole advantage is that this happens
   while other sequences keep decoding);
3. **grow** — on-demand admission allocates each active sequence's next
   decode block as its length crosses a block boundary; pool exhaustion
   preempts the newest resident sequence (swap-out/recompute) until the
   survivors fit;
4. **decode** — one paged decode step over all S slots; active rows
   advance one token, empty rows are masked no-ops;
5. **retire** — finished sequences (stop token or ``max_new_tokens``)
   free their blocks immediately and land in ``completed``.

Sampling is per request and host-side over the returned logits row:
greedy is ``np.argmax`` (bitwise-identical to ``generate``'s
``jnp.argmax`` on identical logits — the bench's floor); ``temperature``
/ ``top_k`` requests thread the same presplit key schedule ``generate``
uses, so a sampled request through the engine reproduces
``generate(..., key=PRNGKey(seed))`` exactly.

Timestamps come from the module-level ``_now`` (monotonic), injectable
for tests the same way ``runtime.supervisor._wall`` is.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from ..models.generate import prefill, prefill_suffix, sample_token
from ..models.transformer import TransformerConfig
from ..obs import MetricsRegistry, record_event
from .batcher import BatcherConfig, ContinuousBatcher, Request, SeqState
from .kv_cache import (
    CacheExhausted,
    PagedCacheConfig,
    export_blocks,
    gather_seq,
    init_pools,
    make_paged_decode_fn,
    write_imported,
    write_prefill,
    write_prefill_at,
    write_swapped,
)
from .migration import MigrationError, pack_kv, unpack_kv

# cache-occupancy histogram buckets: fractions of the allocatable pool in
# use, observed once per scheduling round (engine.report() embeds it)
_OCCUPANCY_BUCKETS = tuple(round(0.1 * i, 1) for i in range(1, 11))

# relative-residual buckets for the serving feedback loop: |predicted -
# measured| / measured of each decode round vs the paged-decode cost
# estimate (serving/costs.py) — ratio-scaled, not ms-scaled
_RESIDUAL_BUCKETS = (0.05, 0.1, 0.2, 0.5, 1.0, 2.0, 5.0, 10.0)

# migration payload size buckets (bytes on the wire, power-of-4-ish):
# tiny bench models ship KB, production shapes ship MB — one histogram
# covers both
_MIGRATION_BYTES_BUCKETS = (
    1 << 10, 1 << 12, 1 << 14, 1 << 16, 1 << 18, 1 << 20, 1 << 22, 1 << 24,
)

__all__ = ["CompletedRequest", "ServingEngine"]

# injection point for tests (patch this, not time.monotonic) — one clock
# for arrival stamps (load generator) and token stamps (engine)
_now = time.monotonic


@dataclasses.dataclass(frozen=True)
class CompletedRequest:
    """A finished request's tokens and latency-relevant timestamps (all
    on the ``_now`` clock): ``ttft_s = first_token_s - arrival_s``;
    per-token decode latency = ``(done_s - first_token_s) / (n - 1)``."""

    rid: int
    tokens: np.ndarray
    arrival_s: float
    admitted_s: float
    first_token_s: float
    done_s: float
    # per-token ``_now`` stamps (first token included): consecutive
    # differences are the inter-token latency samples the disagg bench's
    # decode-p99 floor is computed from
    token_times: tuple = ()

    @property
    def n_tokens(self) -> int:
        return int(self.tokens.shape[0])

    @property
    def intervals_s(self) -> tuple:
        """Inter-token gaps (seconds), one per decode token."""
        return tuple(
            b - a for a, b in zip(self.token_times, self.token_times[1:])
        )

    @property
    def ttft_s(self) -> float:
        return self.first_token_s - self.arrival_s

    @property
    def per_token_s(self) -> float:
        if self.n_tokens <= 1:
            return 0.0
        return (self.done_s - self.first_token_s) / (self.n_tokens - 1)


class ServingEngine:
    def __init__(
        self,
        params,
        cfg: TransformerConfig,
        pcfg: PagedCacheConfig,
        bcfg: BatcherConfig | None = None,
        metrics: MetricsRegistry | None = None,
        fused: bool = True,
        decode_impl: str = "jnp",
        slo_window_s: float = 10.0,
    ):
        self.params = params
        self.cfg = cfg
        self.pcfg = pcfg
        self.bcfg = bcfg or BatcherConfig()
        self.fused = bool(fused)
        self.decode_impl = decode_impl
        # the engine's accounting lives in a metrics registry (shareable —
        # the replica pool passes one per replica so its report is a view
        # over the same counters); per-request timestamps stay on
        # CompletedRequest, the registry carries the aggregates
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        # TTFT carries the SLO, so it is the WINDOWED histogram: the
        # cumulative view dilutes a fresh breach after a quiet hour, the
        # rolling window over slo_window_s is what engine.report() shows
        # AND what the pool arbiter's breach check reads — one instrument,
        # created here so no later plain histogram() call can shadow it
        self.slo_window_s = float(slo_window_s)
        self.metrics.windowed_histogram(
            "serve.ttft_ms", interval_s=self.slo_window_s / 10.0, intervals=10
        )
        self.batcher = ContinuousBatcher(pcfg, self.bcfg)
        self.pools = init_pools(cfg, pcfg)
        # donation keeps steady-state decode allocation-free: the pool
        # scatter aliases in place instead of copying the whole pool every
        # round (measured ~35% of the paged round's cost on the CPU
        # backend, which — on this pin — implements donation warning-free)
        self._decode = make_paged_decode_fn(
            cfg, donate=True, fused=self.fused, impl=decode_impl
        )
        self._prefill = jax.jit(
            lambda p, tok: prefill(p, tok, cfg, max_len=pcfg.max_len)
        )
        # suffix-only prefill for prefix-cache hits, fused with the block
        # gather into ONE program: one compile per (chain_len, cached_len,
        # suffix_len) bucket — the prefix shapes carry the offset, so
        # RoPE/mask come out right with zero dynamic indexing, and the
        # per-layer gather never round-trips through eager dispatch (which
        # costs more than the tokens it saves at small model sizes)
        def _hit(p, tok, pools, chain, c):
            view = gather_seq(pools, chain, length=c)
            return prefill_suffix(
                p, tok,
                {
                    "k": [k[None] for k in view["k"]],
                    "v": [v[None] for v in view["v"]],
                },
                cfg, max_len=pcfg.max_len,
            )

        self._hit_prefill = jax.jit(_hit, static_argnums=(4,))
        self._write = jax.jit(write_prefill, donate_argnums=(0,))
        # the suffix scatter never touches blocks below start_block — the
        # shared cached blocks stay byte-identical through a hit
        self._write_at = jax.jit(
            write_prefill_at, static_argnums=(3,), donate_argnums=(0,)
        )
        self._write_back = jax.jit(write_swapped, donate_argnums=(0,))
        # migrated-KV import scatter: block-shaped arrays straight into
        # the pool (one compile per distinct migrated block count)
        self._write_import = jax.jit(write_imported, donate_argnums=(0,))
        self._keys: dict = {}  # slot -> presplit (max_new, 2) key rows
        # rid -> blocks held for an in-flight migration export; released
        # on the decode side's ack (or the abort path), NEVER before —
        # the bytes on the wire are a VIEW of these blocks until the
        # receiver confirms it owns a copy
        self._exported: dict = {}
        # chaos knob (set by replica_main from FT_RPC_PREFILL_SLEEP):
        # stretches every prefill by this many seconds PER COMPUTED
        # PROMPT TOKEN — prefill cost scales with tokens, so the knob
        # must too — amplifying the prefill-stall mechanism the disagg
        # bench measures at CPU scale
        self.chaos_prefill_sleep_s = 0.0
        self.completed: dict = {}
        self.steps = 0
        self.decode_steps = 0
        # windowed prefix hit-rate over the SLO window (admissions only;
        # exported as a gauge so `obs metrics DIR --prom` carries it)
        self._prefix_window: deque = deque()
        if self.batcher.prefix_index is not None:
            self.batcher.prefix_index.on_evict = self._on_prefix_evict

    # ---- intake ------------------------------------------------------------

    def submit(self, request: Request) -> bool:
        """Queue a request (stamping arrival if the caller didn't)."""
        if request.arrival_s == 0.0:
            request = dataclasses.replace(request, arrival_s=_now())
        ok = self.batcher.submit(request)
        self.metrics.counter(
            "serve.submitted" if ok else "serve.rejected"
        ).inc()
        if not ok:
            record_event(
                "serve_reject", rid=request.rid,
                reason=self.batcher.rejected[-1][1],
            )
        return ok

    @property
    def idle(self) -> bool:
        return self.batcher.idle

    # ---- the scheduling round ----------------------------------------------

    def step(self) -> dict:
        """One resume → admit → grow → decode → retire round; returns
        counters.  Growth (on-demand admission only) allocates each
        active sequence's next decode block; exhaustion preempts the
        newest resident sequence (swap-out or recompute per
        ``BatcherConfig.preempt``) until the rest fit."""
        t0 = _now()
        resumed = self.batcher.try_resume(t0)
        for slot, state, kv in resumed:
            self._resume_slot(slot, state, kv)
        admitted = self.batcher.try_admit(t0)
        if self.batcher.admit_blocked is not None:
            rid, want, free = self.batcher.admit_blocked
            self.metrics.counter("serve.admit_blocked").inc()
            record_event("serve_admit_blocked", rid=rid, want=want, free=free)
        for slot, state in admitted:
            record_event(
                "serve_admit", rid=state.rid, slot=slot,
                prompt_len=state.request.prompt_len,
                blocks=len(state.block_ids),
            )
            self._prefill_slot(slot, state)
        preempted = self._grow_with_preemption()
        active = self.batcher.active_slots()
        if active:
            tables, lengths, tokens, _ = self.batcher.batch_arrays()
            t_dec = _now()
            logits, self.pools = self._decode(
                self.params, self.pools, tables, lengths, tokens
            )
            logits = np.asarray(logits)  # host fetch = the step boundary
            decode_s = _now() - t_dec
            now = _now()
            for slot in active:
                tok = self._pick(slot, logits[slot])
                self.batcher.record_decode_token(slot, tok, now)
            self.decode_steps += 1
            self.metrics.counter("serve.decode_tokens").inc(len(active))
            record_event("serve_decode", n_active=len(active))
            self._round_feedback(
                len(active), int(np.asarray(lengths).max()), decode_s
            )
        finished = self.batcher.retire_ready()
        for slot, state in finished:
            self._keys.pop(slot, None)
            self._complete(state)
        self.steps += 1
        m = self.metrics
        m.counter("serve.rounds").inc()
        m.counter("serve.admitted").inc(len(admitted))
        m.counter("serve.finished").inc(len(finished))
        m.gauge("serve.active_slots").set(self.batcher.num_active)
        free = self.batcher.allocator.num_free
        total = self.pcfg.num_blocks - 1
        m.gauge("serve.free_blocks").set(free)
        m.gauge("serve.active_blocks").set(total - free)
        m.gauge("serve.preempted_seqs").set(len(self.batcher.preempted))
        m.histogram(
            "serve.cache_occupancy", buckets=_OCCUPANCY_BUCKETS
        ).observe((total - free) / total)
        m.histogram("serve.round_ms").observe((_now() - t0) * 1e3)
        return {
            "admitted": len(admitted),
            "resumed": len(resumed),
            "preempted": preempted,
            "decoded": len(active),
            "finished": len(finished),
        }

    def run_until_idle(self, max_steps: int = 100_000) -> None:
        for _ in range(max_steps):
            if self.idle:
                return
            self.step()
        raise RuntimeError(f"engine not idle after {max_steps} steps")

    # ---- internals ---------------------------------------------------------

    def _grow_with_preemption(self) -> int:
        """On-demand growth with the exhaustion → preempt loop: keep
        evicting the newest resident sequence until every survivor's next
        decode block allocates.  Returns how many sequences were
        preempted this round; raises when a lone sequence cannot grow
        (nothing left to evict — submit()'s pool-capacity guard makes
        that unreachable for admissible requests)."""
        preempted = 0
        while True:
            try:
                self.batcher.grow_for_decode()
                return preempted
            except CacheExhausted:
                victim = self.batcher.pick_victim()
                if victim is None:
                    raise
                self._preempt_slot(victim)
                preempted += 1

    def _preempt_slot(self, slot: int) -> None:
        state = self.batcher.slots[slot]
        mode = self.bcfg.preempt
        kv = None
        if mode == "swap":
            # host copies of the written positions — np.asarray moves the
            # bytes off-device NOW, before the freed blocks are rewritten
            view = gather_seq(self.pools, state.block_ids, length=state.length)
            kv = {
                "k": [np.asarray(k) for k in view["k"]],
                "v": [np.asarray(v) for v in view["v"]],
            }
            swapped = sum(a.nbytes for a in kv["k"]) + sum(
                a.nbytes for a in kv["v"]
            )
            self.metrics.counter("serve.swap_out_bytes").inc(swapped)
            self.metrics.counter("serve.swap_outs").inc()
            record_event(
                "serve_swap_out", rid=state.rid, length=state.length,
                bytes=swapped,
            )
        blocks = len(state.block_ids)
        self.batcher.preempt(slot, kv)
        self._keys.pop(slot, None)  # re-derived from the seed on resume
        self.metrics.counter("serve.preempts").inc()
        record_event(
            "serve_preempt", rid=state.rid, slot=slot, mode=mode,
            length=state.length, blocks_freed=blocks,
            n_generated=len(state.generated),
        )

    def _resume_slot(self, slot: int, state: SeqState, kv) -> None:
        req = state.request
        n = len(state.block_ids)
        bs = self.pcfg.block_size
        if kv is not None:
            # swap-in: scatter the exact saved bytes back (zero-padded to
            # whole blocks; the pad sits past the causal bound, invisible
            # until overwritten) — resume is bit-identical by construction
            padded = {"k": [], "v": []}
            for kind in ("k", "v"):
                for a in kv[kind]:
                    full = np.zeros((n * bs, *a.shape[1:]), a.dtype)
                    full[: a.shape[0]] = a
                    padded[kind].append(jnp.asarray(full))
            self.pools = self._write_back(
                self.pools, padded, np.asarray(state.block_ids, np.int32)
            )
        else:
            # recompute: replay the tokens whose K/V were dropped (prompt
            # + already-written decode tokens) through prefill
            written = np.concatenate([
                np.asarray(req.prompt, np.int32),
                np.asarray(
                    state.generated[: state.length - req.prompt_len],
                    np.int32,
                ),
            ])
            _, cache = self._prefill(self.params, written[None])
            self.pools = self._write(
                self.pools, cache, np.asarray(state.block_ids, np.int32)
            )
        if req.temperature > 0:
            # same derivation as _prefill_slot: the schedule is a pure
            # function of the seed, indexed by len(generated) — resume
            # continues exactly where the evicted slot stopped
            self._keys[slot] = jax.random.split(
                jax.random.PRNGKey(req.seed), req.max_new_tokens
            )
        self.metrics.counter("serve.resumes").inc()
        record_event(
            "serve_resume", rid=state.rid, slot=slot,
            mode="swap" if kv is not None else "recompute",
            length=state.length, blocks=n,
        )

    def _round_feedback(
        self, n_active: int, max_len: int, measured_s: float
    ) -> None:
        """The serving-side feedback sample: one decode round's measured
        time against the paged-decode cost estimate (serving/costs.py),
        observed into the ``serve.round_residual`` histogram (the drift
        signal ``engine.report()`` exposes) and emitted as a
        ``serve_round_measured`` span — the serving twin of the training
        stack's ``bucket_measured`` events, rendered beside its
        prediction in the merged timeline."""
        from .costs import predict_decode_round_us

        pred = predict_decode_round_us(
            self.cfg, self.pcfg, n_active, max_len, self._cost_params()
        )
        measured_us = float(measured_s) * 1e6
        predicted_us = pred["predicted_us"]
        rel = abs(predicted_us - measured_us) / max(measured_us, 1e-9)
        self.metrics.histogram(
            "serve.round_residual", buckets=_RESIDUAL_BUCKETS
        ).observe(rel)
        record_event(
            "serve_round_measured",
            round=self.decode_steps,
            n_active=int(n_active),
            max_len=int(max_len),
            measured_us=round(measured_us, 3),
            predicted_us=round(predicted_us, 3),
            compute_us=round(pred["compute_us"], 3),
            bytes_us=round(pred["bytes_us"], 3),
        )

    def _cost_params(self):
        params = getattr(self, "_cost_params_cache", None)
        if params is None:
            from ..planner.calibrate import default_params

            params = self._cost_params_cache = default_params()
        return params

    def _on_prefix_evict(self, block: int) -> None:
        self.metrics.counter("serve.prefix_evictions").inc()
        record_event("serve_prefix_evict", block=int(block))

    def _note_prefix_admission(self, hit: bool, now: float) -> None:
        """One admission's hit/miss into the windowed hit-rate gauge."""
        w = self._prefix_window
        w.append((now, 1 if hit else 0))
        cutoff = now - self.slo_window_s
        while w and w[0][0] < cutoff:
            w.popleft()
        self.metrics.gauge("serve.prefix_hit_rate").set(
            sum(h for _, h in w) / len(w)
        )

    def release_prefix_cache(self) -> int:
        """Drop every index-held block reference (the drain/leak-check
        path: afterwards the free list must be whole again once no
        sequences are resident).  Returns how many entries were
        released."""
        idx = self.batcher.prefix_index
        return idx.clear() if idx is not None else 0

    # ---- prefill/decode disaggregation -------------------------------------

    def prefill_for_migration(self, request: Request, codec: str = "f32"):
        """The PREFILL replica's half of a migration: run the prompt's
        prefill, emit the first token (greedy — the RPC tier carries no
        sampling knobs), and pack the sequence's KV blocks for the wire.

        The blocks stay allocated under ``_exported[rid]`` until
        :meth:`release_exported` — the ack/abort discipline: releasing
        before the decode side confirms admission would let a concurrent
        prefill recycle the blocks while their bytes are still the only
        copy of this sequence's state.  Returns ``None`` when the pool
        cannot hold the prompt right now (the caller refuses the request
        back to the front door); raises :class:`MigrationError` for a
        request that could NEVER migrate (oversized, sampled)."""
        req = request
        if req.temperature > 0:
            raise MigrationError(
                f"request {req.rid}: migration is greedy-only "
                f"(temperature={req.temperature})"
            )
        if req.prompt_len < 1 or req.prompt_len >= self.pcfg.max_len:
            raise MigrationError(
                f"request {req.rid}: prompt_len {req.prompt_len} outside "
                f"(0, max_len={self.pcfg.max_len})"
            )
        if req.rid in self._exported:
            raise MigrationError(
                f"request {req.rid}: migration already in flight"
            )
        n = self.pcfg.blocks_for(req.prompt_len)
        t0 = _now()
        try:
            blocks = self.batcher._alloc_with_evict(n)
        except CacheExhausted:
            self.metrics.counter("serve.migration_export_blocked").inc()
            return None
        record_event(
            "serve_admit", rid=req.rid, slot=-1,
            prompt_len=req.prompt_len, blocks=n, migration=True,
        )
        prompt = np.asarray(req.prompt, np.int32)
        logits, cache = self._prefill(self.params, prompt[None])
        self.pools = self._write(
            self.pools, cache, np.asarray(blocks, np.int32)
        )
        if self.chaos_prefill_sleep_s > 0:
            time.sleep(self.chaos_prefill_sleep_s * req.prompt_len)
        first_token = int(np.argmax(np.asarray(logits[0])))
        kv = export_blocks(self.pools, blocks)
        kv = {
            "k": [np.asarray(a) for a in kv["k"]],
            "v": [np.asarray(a) for a in kv["v"]],
        }
        meta, blob = pack_kv(kv, codec=codec)
        self._exported[req.rid] = blocks
        now = _now()
        self.metrics.counter("serve.migration_exports").inc()
        self.metrics.histogram(
            "serve.migration_bytes", buckets=_MIGRATION_BYTES_BUCKETS
        ).observe(len(blob))
        self.metrics.histogram("serve.ttft_ms").observe(
            (now - req.arrival_s) * 1e3
        )
        from .costs import predict_prefill_us

        record_event(
            "serve_prefill", rid=req.rid, slot=-1,
            prompt_len=req.prompt_len, cached_tokens=0,
            measured_us=round((now - t0) * 1e6, 3),
            predicted_us=round(
                predict_prefill_us(
                    self.cfg, req.prompt_len, self._cost_params()
                ),
                3,
            ),
        )
        return {
            "first_token": first_token,
            "meta": meta,
            "blob": blob,
            "ttft_s": now - req.arrival_s,
            "prefill_s": now - t0,
        }

    def release_exported(self, rid: int, acked: bool) -> bool:
        """Drop the blocks held for ``rid``'s migration export — on the
        decode side's ACK (the handoff succeeded, the receiver owns a
        copy) or on the ABORT path (refused, timed out, receiver died;
        the request goes back to the front door's retry loop).  Exactly
        one release per export, loud counters either way."""
        blocks = self._exported.pop(rid, None)
        if blocks is None:
            return False
        self.batcher.allocator.free(blocks)
        self.metrics.counter(
            "serve.migration_acked" if acked else "serve.migration_aborted"
        ).inc()
        if not acked:
            record_event("serve_migration_abort", rid=rid,
                         blocks=len(blocks))
        return True

    def admit_migrated(self, request: Request, first_token: int,
                       meta: dict, blob: bytes):
        """The DECODE replica's half: verify the payload, land the
        sequence.  Refuse-don't-guess — :class:`MigrationError` for any
        integrity or geometry violation (CRC, shapes, a block count that
        does not match the prompt), ``None`` for a clean capacity
        refusal (no slot / no blocks / resume backlog; the prefill side
        aborts and the front door retries).  On success the sequence is
        resident exactly as if prefill had run locally — length =
        prompt_len, first token recorded, decode continues from the
        imported blocks on the next :meth:`step`."""
        req = request
        total = req.prompt_len + req.max_new_tokens
        if req.prompt_len < 1 or total > self.pcfg.max_len:
            raise MigrationError(
                f"request {req.rid}: prompt+max_new {total} exceeds "
                f"max_len {self.pcfg.max_len}"
            )
        if self.pcfg.blocks_for(total) > self.pcfg.num_blocks - 1:
            raise MigrationError(
                f"request {req.rid}: needs {self.pcfg.blocks_for(total)} "
                f"blocks, pool holds {self.pcfg.num_blocks - 1}"
            )
        kv = unpack_kv(meta, blob)  # CRC + per-tensor verification
        if (
            int(meta["block_size"]) != self.pcfg.block_size
            or int(meta["n_heads"]) != self.cfg.n_heads
            or int(meta["head_dim"]) != self.cfg.head_dim
            or int(meta["n_layers"]) != self.cfg.n_layers
        ):
            raise MigrationError(
                f"request {req.rid}: payload geometry "
                f"(bs={meta['block_size']}, H={meta['n_heads']}, "
                f"Dh={meta['head_dim']}, L={meta['n_layers']}) does not "
                f"match this replica's model"
            )
        n_mig = int(meta["n_blocks"])
        if n_mig != self.pcfg.blocks_for(req.prompt_len):
            raise MigrationError(
                f"request {req.rid}: {n_mig} migrated blocks for a "
                f"{req.prompt_len}-token prompt "
                f"(expected {self.pcfg.blocks_for(req.prompt_len)})"
            )
        now = _now()
        admit = self.batcher.admit_migrated(req, first_token, now)
        if admit is None:
            self.metrics.counter("serve.migration_refused").inc()
            record_event(
                "serve_migration_refuse", rid=req.rid, reason="capacity"
            )
            return None
        slot, state = admit
        kv_dev = {
            "k": [jnp.asarray(a, self.cfg.dtype) for a in kv["k"]],
            "v": [jnp.asarray(a, self.cfg.dtype) for a in kv["v"]],
        }
        self.pools = self._write_import(
            self.pools, kv_dev, np.asarray(state.block_ids[:n_mig], np.int32)
        )
        if self.batcher.prefix_index is not None:
            # mid-stream arrival of already-full blocks: the prompt's
            # FULL blocks are shareable the moment they land, so the
            # index adopts them at admission, not at retirement (the
            # retirement insert walks the same chain idempotently)
            full = req.prompt_len // self.pcfg.block_size
            self.batcher.prefix_index.insert(
                np.asarray(req.prompt), state.block_ids[:full]
            )
        self.metrics.counter("serve.migrations_in").inc()
        self.metrics.histogram(
            "serve.migration_bytes", buckets=_MIGRATION_BYTES_BUCKETS
        ).observe(len(blob))
        record_event(
            "serve_migration_recv", rid=req.rid, slot=slot,
            bytes=len(blob), codec=str(meta.get("codec")), blocks=n_mig,
        )
        return slot

    # ---- prefix-warm drain handoff -----------------------------------------

    def _block_hash(self, block: int) -> str:
        """CRC32 over a block's K and V bytes across every layer — the
        content witness a handoff successor checks its RECOMPUTED block
        against (block bytes are a pure function of the token prefix, so
        agreeing hashes mean the warm cache really is the same cache)."""
        import zlib

        crc = 0
        for kind in ("k", "v"):
            for layer in self.pools[kind]:
                crc = zlib.crc32(np.asarray(layer[block]).tobytes(), crc)
        return f"{crc & 0xFFFFFFFF:08x}"

    def export_prefix_handoff(self) -> dict | None:
        """Serialize the prefix index for a drain handoff: every node as
        its root-to-node token prefix plus the content hash of its block.
        Token ids and hashes travel; block ids and raw K/V bytes never do
        — the successor RECOMPUTES each block from the prefix and uses
        the hash to prove it rebuilt the same bytes.  Returns ``None``
        when the prefix cache is disabled."""
        idx = self.batcher.prefix_index
        if idx is None:
            return None
        entries = [
            {
                "prefix": [int(t) for key in path for t in key],
                "hash": self._block_hash(block),
            }
            for path, block in idx.node_paths()
        ]
        self.metrics.counter("serve.handoff_exported_blocks").inc(
            len(entries)
        )
        record_event("serve_handoff_export", entries=len(entries))
        return {
            "version": 1,
            "block_size": self.pcfg.block_size,
            "entries": entries,
        }

    def prewarm_prefix_from_handoff(self, doc) -> dict:
        """Rebuild a predecessor's prefix cache from its handoff export:
        recompute each prefix's last block via prefill, verify the bytes
        against the recorded content hash, and adopt verified blocks into
        this replica's index BEFORE traffic arrives.  A hash mismatch
        refuses that entry (and, since children need their parent chain,
        its whole subtree) — a corrupt handoff degrades to a cold start,
        never to serving wrong K/V.  Returns stats counters."""
        stats = {"inserted": 0, "skipped": 0, "hash_mismatches": 0,
                 "refused": None}
        idx = self.batcher.prefix_index
        if idx is None:
            stats["refused"] = "prefix cache disabled"
            return stats
        bs = self.pcfg.block_size
        if (
            not isinstance(doc, dict)
            or doc.get("version") != 1
            or int(doc.get("block_size", -1)) != bs
            or not isinstance(doc.get("entries"), list)
        ):
            stats["refused"] = "incompatible handoff payload"
            self.metrics.counter("serve.handoff_refused").inc()
            record_event("serve_handoff_refused",
                         reason=stats["refused"])
            return stats
        alloc = self.batcher.allocator
        # parents sort before their children (tuple-prefix order), so a
        # single pass builds chains bottom-up; keep one sequence's worth
        # of blocks free so prewarming can never starve first admission
        reserve = self.pcfg.blocks_per_seq
        for e in sorted(doc["entries"], key=lambda e: len(e["prefix"])):
            prefix = e.get("prefix")
            if (
                not isinstance(prefix, list) or not prefix
                or len(prefix) % bs != 0
            ):
                stats["skipped"] += 1
                continue
            tokens = np.asarray(prefix, np.int32)
            n = len(prefix) // bs
            matched = idx.match(tokens)
            if len(matched) >= n:
                continue  # already warm (shared parent of two subtrees)
            if len(matched) < n - 1:
                stats["skipped"] += 1  # parent refused/missing upstream
                continue
            if alloc.num_free <= reserve:
                stats["skipped"] += 1
                continue
            [b] = alloc.alloc(1)
            _, cache = self._prefill(self.params, tokens[None])
            self.pools = self._write_at(
                self.pools, cache, np.asarray([b], np.int32), n - 1
            )
            want = e.get("hash")
            if want is not None and self._block_hash(b) != want:
                alloc.release([b])
                stats["hash_mismatches"] += 1
                self.metrics.counter("serve.handoff_hash_mismatch").inc()
                record_event(
                    "serve_handoff_hash_mismatch", prefix_len=len(prefix)
                )
                continue
            idx.insert(tokens, matched + [b])
            alloc.release([b])  # the index's retain is now the holder
            stats["inserted"] += 1
        self.metrics.counter("serve.handoff_prewarmed_blocks").inc(
            stats["inserted"]
        )
        record_event("serve_handoff_prewarm", **stats)
        return stats

    def _prefill_slot(self, slot: int, state: SeqState) -> None:
        t0 = _now()
        req = state.request
        prompt = np.asarray(req.prompt, np.int32)
        c = state.cached_tokens
        if c > 0:
            bs = self.pcfg.block_size
            # the prefix K/V lives in the shared blocks — plus, for a
            # full-prompt hit, the COW fork's SOURCE (the fresh fork
            # destination in block_ids holds garbage until the scatter
            # below fills it with the same bytes)
            chain = list(state.block_ids[: state.shared_blocks])
            if state.cow_src is not None:
                chain.append(state.cow_src)
            logits, cache = self._hit_prefill(
                self.params, prompt[None, c:], self.pools,
                np.asarray(chain, np.int32), c,
            )
            # scatter ONLY from the first non-shared block onward: the
            # cache's positions there are the gathered prefix bytes (for
            # the COW fork's mid-block head) plus the freshly computed
            # suffix K/V; the shared blocks below are never rewritten
            sb = c // bs
            self.pools = self._write_at(
                self.pools, cache,
                np.asarray(state.block_ids[sb:], np.int32), sb,
            )
            if state.cow_src is not None:
                self.metrics.counter("serve.prefix_cow").inc()
                record_event(
                    "serve_prefix_cow", rid=req.rid,
                    src=int(state.cow_src), dst=int(state.block_ids[sb]),
                )
                self.batcher.allocator.release([state.cow_src])
                state.cow_src = None
            self.metrics.counter("serve.prefix_hits").inc()
            self.metrics.counter("serve.cached_tokens_saved").inc(c)
            record_event(
                "serve_prefix_hit", rid=req.rid, cached_tokens=c,
                shared_blocks=state.shared_blocks,
                suffix_tokens=req.prompt_len - c,
            )
        else:
            logits, cache = self._prefill(self.params, prompt[None])
            self.pools = self._write(
                self.pools, cache, np.asarray(state.block_ids, np.int32)
            )
            if self.batcher.prefix_index is not None:
                self.metrics.counter("serve.prefix_misses").inc()
        if self.batcher.prefix_index is not None:
            self._note_prefix_admission(c > 0, t0)
        if self.chaos_prefill_sleep_s > 0:
            # per COMPUTED token: a prefix-cache hit only pays its suffix
            time.sleep(self.chaos_prefill_sleep_s * (req.prompt_len - c))
        if req.temperature > 0:
            if req.seed is None:  # unreachable via submit(); guard direct use
                raise ValueError(
                    f"request {req.rid}: temperature > 0 requires seed="
                )
            # the SAME presplit schedule generate() uses, so a sampled
            # request reproduces generate(key=PRNGKey(seed)) exactly
            self._keys[slot] = jax.random.split(
                jax.random.PRNGKey(req.seed), req.max_new_tokens
            )
        tok = self._pick(slot, np.asarray(logits[0]))
        now = _now()
        self.batcher.record_first_token(slot, tok, now)
        self.metrics.histogram("serve.ttft_ms").observe(
            (now - req.arrival_s) * 1e3
        )
        from .costs import predict_prefill_us

        record_event(
            "serve_prefill", rid=req.rid, slot=slot,
            prompt_len=req.prompt_len, cached_tokens=c,
            measured_us=round((now - t0) * 1e6, 3),
            predicted_us=round(
                predict_prefill_us(
                    self.cfg, req.prompt_len, self._cost_params(),
                    cached_tokens=c,
                ),
                3,
            ),
        )

    def _pick(self, slot: int, logits_row: np.ndarray) -> int:
        state = self.batcher.slots[slot]
        req = state.request
        if req.temperature <= 0:
            return int(np.argmax(logits_row))
        key = self._keys[slot][len(state.generated)]
        tok = sample_token(
            logits_row[None],
            temperature=req.temperature,
            top_k=req.top_k,
            key=key,
        )
        return int(np.asarray(tok)[0])

    def _complete(self, state: SeqState) -> None:
        done = CompletedRequest(
            rid=state.rid,
            tokens=np.asarray(state.generated, np.int32),
            arrival_s=state.request.arrival_s,
            admitted_s=state.admitted_s,
            first_token_s=state.first_token_s,
            done_s=state.done_s,
            token_times=tuple(state.token_times),
        )
        self.completed[state.rid] = done
        if done.n_tokens > 1:
            self.metrics.histogram("serve.per_token_ms").observe(
                done.per_token_s * 1e3
            )
        record_event("serve_retire", rid=state.rid, n_tokens=done.n_tokens,
                     ttft_ms=round(done.ttft_s * 1e3, 3))

    def report(self) -> dict:
        """The replica's accounting: a VIEW over its metrics registry
        (one snapshot — counters, gauges, TTFT/round-time histograms)
        plus the loop counters the pool reads directly."""
        return {
            "steps": self.steps,
            "decode_steps": self.decode_steps,
            "completed": len(self.completed),
            **self.metrics.snapshot(),
        }

    # ---- warmup ------------------------------------------------------------

    def warmup(
        self, prompt_lens, block_counts=(), suffix_buckets=(),
        import_counts=(),
    ) -> None:
        """Compile the decode step, each distinct prompt length's prefill,
        and each distinct reservation size's pool write before a timed run
        (compiles otherwise land inside the first requests' latency).
        ``block_counts``: the distinct ``pcfg.blocks_for(prompt + max_new)``
        values the workload will reserve.  Under on-demand admission the
        swap-in scatter is warmed for EVERY block count (a resume's count
        is ``length//bs + 1`` at whatever length eviction struck — one
        scatter compile per count, and an unwarmed one lands inside the
        preemption stall it is supposed to be ending).
        ``suffix_buckets``: ``(cached_len, suffix_len)`` pairs the
        prefix-cache workload will hit — suffix prefill compiles per
        distinct pair (the prefix shape carries the offset), and an
        unwarmed bucket puts its compile inside the very TTFT the cache
        hit was supposed to shrink.  Each bucket also warms the offset
        scatter for every remaining-block count it can need."""
        S, P = self.bcfg.slots, self.pcfg.blocks_per_seq
        jax.block_until_ready(
            self._decode(
                self.params,
                init_pools(self.cfg, self.pcfg),
                np.zeros((S, P), np.int32),
                np.zeros((S,), np.int32),
                np.zeros((S,), np.int32),
            )[0]
        )
        cache = None
        for t in sorted(set(int(t) for t in prompt_lens)):
            _, cache = self._prefill(self.params, np.zeros((1, t), np.int32))
        for n in sorted(set(int(n) for n in block_counts)):
            if cache is None:
                _, cache = self._prefill(
                    self.params, np.zeros((1, 1), np.int32)
                )
            jax.block_until_ready(
                self._write(
                    init_pools(self.cfg, self.pcfg),
                    cache,
                    np.arange(1, n + 1, dtype=np.int32),
                )["k"][0]
            )
        if self.batcher.ondemand:
            # on-demand writes use block counts the caller's reservation
            # math never names: admission scatters blocks_for(prompt)
            # blocks and recompute-resume scatters length//bs + 1 — warm
            # the prefill write AND the swap-in scatter for every count,
            # or the compile lands inside the TTFT / preemption stall it
            # was supposed to end
            bs = self.pcfg.block_size
            shape = (self.cfg.n_heads, self.cfg.head_dim)
            if cache is None:
                _, cache = self._prefill(
                    self.params, np.zeros((1, 1), np.int32)
                )
            for n in range(1, P + 1):
                jax.block_until_ready(
                    self._write(
                        init_pools(self.cfg, self.pcfg),
                        cache,
                        np.arange(1, n + 1, dtype=np.int32),
                    )["k"][0]
                )
                zeros = [
                    jnp.zeros((n * bs, *shape), self.cfg.dtype)
                    for _ in range(self.cfg.n_layers)
                ]
                jax.block_until_ready(
                    self._write_back(
                        init_pools(self.cfg, self.pcfg),
                        {"k": zeros, "v": zeros},
                        np.arange(1, n + 1, dtype=np.int32),
                    )["k"][0]
                )
        # migrated-KV import scatter: one compile per inbound block
        # count — an unwarmed one stalls the decode replica's engine
        # loop mid-handoff, landing inside the very inter-token p99 the
        # disaggregation exists to protect
        shape = (self.pcfg.block_size, self.cfg.n_heads, self.cfg.head_dim)
        for n in sorted(set(int(n) for n in import_counts)):
            zeros = [
                jnp.zeros((n, *shape), self.cfg.dtype)
                for _ in range(self.cfg.n_layers)
            ]
            jax.block_until_ready(
                self._write_import(
                    init_pools(self.cfg, self.pcfg),
                    {"k": zeros, "v": zeros},
                    np.arange(1, n + 1, dtype=np.int32),
                )["k"][0]
            )
        bs = self.pcfg.block_size
        for c, s in sorted(set((int(c), int(s)) for c, s in suffix_buckets)):
            if c < 1 or s < 1:
                # c need NOT be block-aligned: the COW case caches
                # prompt_len - 2, which lands mid-block in the fork
                raise ValueError(
                    f"suffix bucket ({c}, {s}): cached_len and "
                    f"suffix_len must both be >= 1"
                )
            nc = -(-c // bs)  # chain blocks covering the cached prefix
            _, cache = self._hit_prefill(
                self.params, np.zeros((1, s), np.int32),
                init_pools(self.cfg, self.pcfg),
                np.arange(1, nc + 1, dtype=np.int32), c,
            )
            sb = c // bs
            for n in range(1, P - sb + 1):
                jax.block_until_ready(
                    self._write_at(
                        init_pools(self.cfg, self.pcfg),
                        cache,
                        np.arange(1, n + 1, dtype=np.int32),
                        sb,
                    )["k"][0]
                )
