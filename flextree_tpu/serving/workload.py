"""Shared spike workload generator for the elastic-serving drivers.

``tools/arbiter_spike.py`` (in-process pool under an arrival burst) and
``tools/serve_elastic_chaos.py`` (real-process fleet under lease chaos)
both need the same thing: a three-phase open-loop Poisson arrival
process — baseline → spike → baseline — with a decode-heavy output mix.
One generator lives here so the two drivers cannot drift apart on what
"a burst" means (and so their seeds reproduce the same request stream).

Arrivals are open-loop: each request carries an ``arrival_s`` offset
from the run start and lands on the wall clock whether or not the
serving side keeps up — that is what makes an under-provisioned phase
actually breach the SLO instead of self-throttling.

``prefix_pool`` / ``prefix_frac`` opt a fraction of prompts into shared
token prefixes (drawn per-request from the pool) — the prefix-cache /
affinity-handoff workloads need hot prefixes; the plain spike driver
leaves them off.  Disabled, the RNG draw sequence is identical to the
historical ``arbiter_spike.build_workload``, so existing seeds replay
the exact same stream.
"""

from __future__ import annotations

import numpy as np

from .batcher import Request

__all__ = [
    "PROMPT_LENS",
    "OUT_LENS",
    "OUT_PROBS",
    "build_spike_workload",
]

PROMPT_LENS = (4, 6, 8)
# decode-heavy mixed outputs: mean ~29 tokens = ~190 ms of service at the
# measured round time, so 2 slots/replica caps one replica near 11 rps
OUT_LENS = (16, 32, 48)
OUT_PROBS = (0.4, 0.35, 0.25)


def _poisson_phase(rng, rate: float, duration_s: float, offset_s: float):
    """Arrival offsets of one open-loop Poisson phase."""
    out = []
    t = 0.0
    while t < duration_s:
        t += rng.exponential(1.0 / rate)
        if t < duration_s:
            out.append(offset_s + t)
    return out


def build_spike_workload(
    seed,
    base_rate,
    spike_rate,
    t_base,
    t_spike,
    t_tail,
    *,
    prompt_lens=PROMPT_LENS,
    out_lens=OUT_LENS,
    out_probs=OUT_PROBS,
    vocab: int = 128,
    prefix_pool=(),
    prefix_frac: float = 0.0,
    rid_base: int = 0,
):
    """Requests with ``arrival_s`` offsets covering baseline → spike →
    baseline; returns ``(requests, spike_start_s, spike_end_s)``.

    With ``prefix_pool`` non-empty, each request is prefix-shared with
    probability ``prefix_frac``: a prefix (an int32 token array) drawn
    uniformly from the pool is prepended to its random suffix of
    ``prompt_lens`` tokens — the shape a prefix cache (and the front
    door's affinity routing) can actually exploit.
    """
    rng = np.random.default_rng(seed)
    arrivals = _poisson_phase(rng, base_rate, t_base, 0.0)
    spike_start = float(t_base)
    arrivals += _poisson_phase(rng, spike_rate, t_spike, spike_start)
    spike_end = spike_start + float(t_spike)
    arrivals += _poisson_phase(rng, base_rate, t_tail, spike_end)
    requests = []
    for i, a in enumerate(sorted(arrivals)):
        p = int(rng.choice(prompt_lens))
        m = int(rng.choice(out_lens, p=out_probs))
        prompt = rng.integers(0, vocab, (p,)).astype(np.int32)
        if prefix_pool and rng.random() < prefix_frac:
            pre = np.asarray(
                prefix_pool[int(rng.integers(0, len(prefix_pool)))],
                np.int32,
            )
            prompt = np.concatenate([pre, prompt])
        requests.append(
            Request(
                rid=rid_base + i,
                prompt=prompt,
                max_new_tokens=m,
                arrival_s=float(a),
            )
        )
    return requests, spike_start, spike_end
